"""Per-role scaling-signal collection from engine ``/metrics`` endpoints.

The collector reuses the EPP scrape posture (plain Prometheus text over
HTTP, ``router.picker.scrape_metrics``) but keeps what the picker throws
away: the TTFT histogram's ``le`` buckets, which become a **windowed**
p90 — each scrape diffs the cumulative bucket counts against the
previous scrape per endpoint and pools the deltas across the role, so
the signal reflects requests served *since the last control-loop tick*,
not the process's lifetime average (a lifetime p90 would never move
under a fresh load spike).

Failure posture, per PR 1's resilience layer: every endpoint scrape runs
under a :class:`RetryPolicy` and feeds a per-endpoint
:class:`CircuitBreaker`.  A partitioned endpoint's breaker opens and its
scrapes stop burning the loop's budget; its last sample is reused only
while younger than ``stale_after_s`` and **discarded** after that — a
role with zero usable samples yields ``None`` and the control loop holds
its last-known-good recommendation instead of scaling on fiction.

No direct ``time.time()``/``time.sleep()`` here (enforced by
``tools/lint_resilience.py``): the clock is injected so chaos tests
drive staleness and breaker windows deterministically, and retry pacing
uses an injectable sleep (default: an Event wait).
"""

from __future__ import annotations

import logging
import re
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from fusioninfer_tpu.engine.metrics import histogram_quantile
from fusioninfer_tpu.resilience import CircuitBreaker, RetryPolicy

logger = logging.getLogger("fusioninfer.autoscale.collector")

# the vLLM-compatible families the engine exports (engine/metrics.py)
WAITING = "vllm:num_requests_waiting"
RUNNING = "vllm:num_requests_running"
KV_USAGE = "vllm:kv_cache_usage_perc"
TTFT_BUCKET = "vllm:time_to_first_token_seconds_bucket"

_LE_RE = re.compile(r'le="([^"]+)"')


def parse_engine_sample(text: str) -> tuple[dict[str, float], dict[float, float]]:
    """Prometheus text → ({family: value}, {le: cumulative TTFT count}).

    Label sets other than ``le`` are ignored (one model per engine
    server); ``+Inf`` maps to ``float("inf")``.
    """
    gauges: dict[str, float] = {}
    ttft: dict[float, float] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        head, _, value = line.rpartition(" ")
        try:
            v = float(value)
        except ValueError:
            continue
        name = head.split("{", 1)[0]
        if name == TTFT_BUCKET:
            m = _LE_RE.search(head)
            if not m:
                continue
            le = float("inf") if m.group(1) == "+Inf" else float(m.group(1))
            ttft[le] = v
        else:
            gauges[name] = v
    return gauges, ttft


def http_fetch(url: str, timeout: float = 5.0) -> str:
    """Default transport: GET ``{url}/metrics``, raising on any failure
    (the retry/breaker wrapping happens in the collector)."""
    with urllib.request.urlopen(f"{url}/metrics", timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace")


@dataclass
class EndpointSample:
    """One endpoint's scrape, stamped with the collector clock."""

    name: str
    waiting: float
    running: float
    kv_cache_usage: float
    ttft: dict[float, float]  # le -> cumulative count
    at: float

    @property
    def in_flight(self) -> float:
        return self.waiting + self.running


@dataclass
class RoleSignals:
    """Aggregated per-role signals one control-loop tick scales on."""

    queue_length: float  # mean waiting requests per replica
    kv_cache_utilization: float  # mean usage across replicas
    ttft_p90_s: Optional[float]  # windowed p90; None = no new requests
    in_flight: float  # total waiting+running across replicas
    fresh_endpoints: int  # endpoints scraped live this tick
    stale_endpoints: int  # endpoints carried on a recent last sample
    samples: dict[str, EndpointSample] = field(default_factory=dict)


class MetricsCollector:
    """Scrapes endpoints and aggregates :class:`RoleSignals` per role."""

    def __init__(
        self,
        fetch: Callable[[str], str] = http_fetch,
        clock: Callable[[], float] = time.monotonic,
        retry: Optional[RetryPolicy] = None,
        sleep: Optional[Callable[[float], None]] = None,
        breaker_factory: Optional[Callable[[], CircuitBreaker]] = None,
        stale_after_s: float = 30.0,
    ):
        self._fetch = fetch
        self._clock = clock
        self._retry = retry or RetryPolicy(
            max_attempts=2, base_delay_s=0.1, max_delay_s=0.5)
        # time.sleep is banned in this package; an Event wait is the
        # same blocking primitive with an injectable override
        self._sleep = sleep or threading.Event().wait
        self._breaker_factory = breaker_factory or (
            lambda: CircuitBreaker(
                failure_threshold=3, recovery_timeout_s=30.0, clock=clock)
        )
        self.stale_after_s = stale_after_s
        self._breakers: dict[str, CircuitBreaker] = {}
        self._last: dict[str, EndpointSample] = {}
        self._prev_ttft: dict[str, dict[float, float]] = {}

    # -- per-endpoint --

    def breaker(self, name: str) -> CircuitBreaker:
        b = self._breakers.get(name)
        if b is None:
            b = self._breakers[name] = self._breaker_factory()
        return b

    def scrape(self, name: str, url: str) -> Optional[EndpointSample]:
        """One endpoint, through its breaker and the retry policy.
        Returns None when the endpoint is unreachable or circuit-broken
        (the caller decides whether a recent last sample may stand in)."""
        breaker = self.breaker(name)
        if not breaker.allow():
            return None
        try:
            text = self._retry.run(
                lambda: self._fetch(url),
                sleep=self._sleep,
                clock=self._clock,
            )
        except Exception as e:  # fetch may raise anything; RetryBudgetExhausted included
            breaker.record_failure()
            logger.warning("scrape %s (%s) failed: %s", name, url, e)
            return None
        breaker.record_success()
        gauges, ttft = parse_engine_sample(text)
        sample = EndpointSample(
            name=name,
            waiting=gauges.get(WAITING, 0.0),
            running=gauges.get(RUNNING, 0.0),
            kv_cache_usage=gauges.get(KV_USAGE, 0.0),
            ttft=ttft,
            at=self._clock(),
        )
        self._last[name] = sample
        return sample

    def in_flight(self, name: str, url: str) -> Optional[float]:
        """Fresh waiting+running for one endpoint (drain polling).
        None when unreachable — the drainer keeps waiting rather than
        treating silence as idle."""
        sample = self.scrape(name, url)
        return None if sample is None else sample.in_flight

    # -- per-role --

    def collect(self, endpoints: Sequence[tuple[str, str]]) -> Optional[RoleSignals]:
        """Aggregate signals for one role's ``[(name, url), ...]``.

        Returns None when not a single endpoint produced a usable sample
        (all partitioned with stale last samples) — the caller must hold
        its last recommendation, not decide on nothing.

        One collector is shared across roles/services, so collect()
        never evicts state for endpoints it wasn't handed — the control
        loop calls :meth:`retain` once per tick with the full live set.
        """
        now = self._clock()
        usable: list[EndpointSample] = []
        fresh = stale = 0
        fresh_names: list[str] = []
        for name, url in endpoints:
            sample = self.scrape(name, url)
            if sample is not None:
                usable.append(sample)
                fresh += 1
                fresh_names.append(name)
                continue
            last = self._last.get(name)
            if last is not None and now - last.at <= self.stale_after_s:
                usable.append(last)
                stale += 1
            elif last is not None:
                # stale beyond the window: discard, never scale on it
                del self._last[name]
                self._prev_ttft.pop(name, None)
        if fresh == 0:
            # recent-but-stale samples may FILL IN alongside live ones
            # (partial partition), but must never drive a decision alone:
            # scaling a fully-partitioned role on its last readings is
            # guessing with confidence
            return None
        n = len(usable)
        signals = RoleSignals(
            queue_length=sum(s.waiting for s in usable) / n,
            kv_cache_utilization=sum(s.kv_cache_usage for s in usable) / n,
            ttft_p90_s=self._windowed_ttft_p90(
                [s for s in usable if s.name in fresh_names]),
            in_flight=sum(s.in_flight for s in usable),
            fresh_endpoints=fresh,
            stale_endpoints=stale,
            samples={s.name: s for s in usable},
        )
        return signals

    def _windowed_ttft_p90(self, samples: list[EndpointSample]) -> Optional[float]:
        """Pool per-endpoint bucket deltas since the previous scrape and
        take the p90.  A counter reset (restarted engine) is detected
        per ENDPOINT — any bucket going backwards voids the whole
        previous sample and that endpoint's delta is its full cumulative
        counts; mixing reset and non-reset buckets would produce a
        non-monotone pooled array and a bogus quantile."""
        pooled: dict[float, float] = {}
        for s in samples:
            if not s.ttft:
                continue
            prev = self._prev_ttft.get(s.name)
            if prev is not None and any(
                    s.ttft.get(le, 0.0) < before for le, before in prev.items()):
                prev = None  # reset: first window since restart
            for le, cum in s.ttft.items():
                before = prev.get(le, 0.0) if prev else 0.0
                pooled[le] = pooled.get(le, 0.0) + (cum - before)
            self._prev_ttft[s.name] = dict(s.ttft)
        if not pooled:
            return None
        bounds = sorted(le for le in pooled if le != float("inf"))
        cumulative = [pooled[le] for le in bounds]
        cumulative.append(pooled.get(float("inf"), cumulative[-1] if cumulative else 0.0))
        return histogram_quantile(bounds, cumulative, 0.9)

    def retain(self, names: set[str]) -> None:
        """Evict state for endpoints that left the fleet — replica churn
        must not grow the breaker/sample dicts forever.  Callers pass the
        union of every endpoint still live across ALL roles they collect
        for (a per-role set would evict the other roles' breakers)."""
        for d in (self._breakers, self._last, self._prev_ttft):
            for name in list(d):
                if name not in names:
                    del d[name]
