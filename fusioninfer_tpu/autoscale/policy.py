"""HPA-style target-value control law, slice-granular.

The core ratio is Kubernetes HPA's: ``desired = ceil(current * actual /
target)``, with a tolerance band around 1.0 so measurement noise never
flaps replicas.  Two deliberate departures for TPU serving:

* **Whole-slice rounding.**  One replica is one gang-scheduled TPU slice
  (``role.tpu`` shape); fractional capacity does not exist, so desired
  replicas always round UP to the next whole slice — under-provisioning
  a prefill fleet shows up as TTFT violations for every user, while the
  cost of one extra slice is bounded.
* **Asymmetric stabilization.**  Scale up reacts fast (window defaults
  to 0: a queue spike is users waiting *now*); scale down holds the MAX
  recommendation seen inside ``scale_down_stabilization_s`` before
  shrinking, because giving a slice back costs a drain + a gang
  reschedule + cold caches — flapping down is far more expensive than
  holding one tick too long.

Clamping to ``[min_replicas, max_replicas]`` is reported via
``Decision.limited`` so the operator can surface a ``ScalingLimited``
condition instead of silently pinning at a bound.

No wall-clock access here (``tools/lint_resilience.py`` enforces it):
the clock arrives injected so stabilization windows run deterministically
under test.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Optional

from fusioninfer_tpu.api.types import AutoscalingSpec

# |actual/target - 1| below this is noise, not pressure (HPA default)
TOLERANCE = 0.1


@dataclass
class Decision:
    """One control-loop verdict for one role."""

    desired: int
    current: int
    raw: int  # pre-stabilization, pre-clamp recommendation
    limited: bool = False
    limit_reason: str = ""  # "AtMaxReplicas" | "AtMinReplicas" | ""
    reasons: list[str] = field(default_factory=list)  # per-signal audit trail

    @property
    def direction(self) -> str:
        if self.desired > self.current:
            return "up"
        if self.desired < self.current:
            return "down"
        return "hold"


def desired_for_ratio(current: int, ratio: float) -> int:
    """The HPA ratio with the tolerance dead-band and slice ceil."""
    if abs(ratio - 1.0) <= TOLERANCE:
        return current
    return max(1, math.ceil(current * ratio))


class ScalingPolicy:
    """Stabilized recommendation stream for ONE role.

    Feed it raw per-tick recommendations (the max across the role's
    signals); it applies the asymmetric stabilization windows and the
    min/max clamp.
    """

    def __init__(self, spec: AutoscalingSpec, clock: Callable[[], float]):
        self.spec = spec
        self._clock = clock
        self._history: list[tuple[float, int]] = []  # (t, raw desired)
        # when continuous observation began (first decide); a window is
        # "covered" only once we have watched the role for its full span
        self._since: Optional[float] = None

    def _prune(self, now: float) -> None:
        horizon = max(self.spec.scale_up_stabilization_s,
                      self.spec.scale_down_stabilization_s)
        self._history = [(t, r) for t, r in self._history if now - t <= horizon]

    def decide(self, current: int, raw: int,
               reasons: Optional[list[str]] = None) -> Decision:
        now = self._clock()
        # coverage restarts whenever observation restarts: first decide
        # ever, or after a gap long enough that the whole history aged
        # out (e.g. the role was fully partitioned for a window's span —
        # its first post-recovery tick must not read as "window covered"
        # and shrink on one momentary lull)
        self._prune(now)
        if self._since is None or not self._history:
            self._since = now
        self._history.append((now, raw))
        desired = raw
        if desired > current and self.spec.scale_up_stabilization_s > 0:
            # up-window: the MIN recommendation across the window must
            # still call for growth, and the window must actually be
            # covered — one spiky tick (or a loop that just started)
            # does not buy a slice
            window = [r for t, r in self._history
                      if now - t <= self.spec.scale_up_stabilization_s]
            if now - self._since < self.spec.scale_up_stabilization_s:
                window.append(current)
            desired = max(current, min(window))
        if desired < current:
            # down-window: hold the MAX recent recommendation — shrink
            # only once the whole window agrees the capacity is excess.
            # Like the up path, the window must be COVERED: a freshly
            # (re)started controller has no history (policies live in
            # memory) and must not drain slices on its first-tick view
            # of a momentary lull
            window = [r for t, r in self._history
                      if now - t <= self.spec.scale_down_stabilization_s]
            if now - self._since < self.spec.scale_down_stabilization_s:
                window.append(current)
            desired = min(current, max(window))
        clamped = min(max(desired, self.spec.min_replicas), self.spec.max_replicas)
        limited = clamped != desired or (
            # also limited when pressure calls past a bound we already sit at
            raw > self.spec.max_replicas and current >= self.spec.max_replicas
        ) or (
            raw < self.spec.min_replicas and current <= self.spec.min_replicas
        )
        reason = ""
        if limited:
            reason = ("AtMaxReplicas" if max(desired, raw) > self.spec.max_replicas
                      else "AtMinReplicas")
        return Decision(
            desired=clamped,
            current=current,
            raw=raw,
            limited=limited,
            limit_reason=reason,
            reasons=list(reasons or []),
        )
